"""Open-loop load generator for the serving control plane.

Closed-loop clients (bench.py's original ``--serving`` harness, the
serving smoke) can never observe overload: each client waits for its
answer before sending the next request, so the offered rate gracefully
degrades to whatever the server sustains and p99 looks flattering.
Production traffic does not wait.  This generator is **open-loop**: a
seeded Poisson process schedules arrivals ahead of time and fires them
at their scheduled instants whether or not earlier requests completed —
when the server falls behind, latency (measured from the *scheduled*
arrival, client-side queueing included) and the error mix show it
honestly.

* **Seeded** (``--seed``): the arrival schedule, the model mix and the
  batch-size mix are all drawn from one ``numpy.random.RandomState`` —
  two runs with the same seed offer byte-identical traffic, so CI can
  assert an SLO on a fixed workload.
* **Mixed models**: each arrival routes to one of the registry's
  models (weighted draw), exercising cross-model fairness and the
  per-model metric labels.
* **Mixed batch shapes**: request sizes draw log-uniformly over
  ``1..max_batch``, sweeping the engine's whole bucket ladder.
* **SLO report**: requests per second offered vs achieved, latency
  p50/p90/p95/p99/p999, and **goodput** — completed-OK responses
  within ``slo_ms`` (``root.common.serving.slo_ms``) per second.
  Under overload goodput is the number that matters: a server
  answering everything late has throughput but no goodput.
* **Priority mix** (``--priority-mix high:1,normal:2,low:1``): each
  arrival draws a priority lane from a weighted, SEPARATELY seeded
  stream (the arrival/model/rows tape is untouched by adding a mix),
  rides the ``X-Priority`` header, and the report grows per-priority
  goodput/latency/shed blocks — ``--assert-goodput-pct high:90``
  gates one lane's goodput specifically (the overload contract:
  low sheds first, high holds).
* **Per-generation attribution**: every HTTP reply's
  ``X-Serving-Generation`` header is retained per request, and the
  report grows a ``per_generation`` block (requests, share of
  traffic, goodput, latency tail per ``gen_<N>`` label) — during a
  canary release the ``share_pct`` IS the observed split, so a
  release run asserts the ladder percentage client-side.
* **Relative overload gate** (``--assert-goodput-gap high:low:10``):
  gates the high-vs-low goodput GAP instead of an absolute number —
  on a slow machine every absolute goodput sags together while the
  priority contract (low sheds first) still holds.
* **Binary bodies** (``--npy``): raw ``.npy`` payloads over
  keep-alive connections for capacity/fleet-scaling measurements —
  microseconds of codec per request instead of the JSON
  milliseconds.
* **Exact quantiles, per model × per bucket**: every completed
  request's latency is RETAINED and percentiles come from
  :func:`znicz_tpu.serving.latency.exact_percentile` (sorted order
  statistics + linear interpolation — never a bucketed
  approximation).  Besides the global block, the report breaks
  latency down per model AND per shape bucket — the bucket the
  request's OWN rows pad into (its nominal bucket; a coalescing
  batcher may ride some requests through a larger bucket's
  executable, so read the breakdown as "tail by request size", the
  client-side view) — so a tail regression on one request class of
  one model is visible in the artifact, not averaged away.

Two runners share the report:

* :func:`run` drives any ``submit(model, x, timeout_ms) -> Future``
  (in process — ``bench.py --serving`` wires it straight into a
  :class:`~znicz_tpu.serving.continuous.ContinuousBatcher`);
* the CLI drives a live server over HTTP, discovering the model fleet
  and sample shapes from ``GET /models``::

      python tools/loadgen.py http://127.0.0.1:8899 \\
          --rate 200 --duration 10 --seed 7 --assert-goodput-pct 90

Exit codes (CLI): 0 = ran (and SLO assertion held, when given),
1 = ``--assert-goodput-pct`` violated, 2 = usage error.
"""

import argparse
import json
import math
import os
import sys
import threading
import time

import numpy

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


class ModelSpec(object):
    """One routable target: ``name`` (None = the server's default
    route), per-sample input shape, the largest request to draw, its
    share of the traffic mix, and the model's shape-bucket ladder
    (defaults to the engine's power-of-two ladder; ``discover_models``
    adopts the server's recorded ladder) — the per-bucket latency
    breakdown attributes each request to the bucket its rows pad
    into."""

    __slots__ = ("name", "sample_shape", "max_batch", "weight",
                 "buckets")

    def __init__(self, name, sample_shape, max_batch=8, weight=1.0,
                 buckets=None):
        self.name = name
        self.sample_shape = tuple(int(d) for d in sample_shape)
        self.max_batch = max(1, int(max_batch))
        self.weight = float(weight)
        if buckets:
            self.buckets = tuple(sorted(int(b) for b in buckets))
        else:
            # the engine's own default ladder rule — never a local
            # re-implementation that could drift (lazy import keeps
            # plain CLI startup light)
            from znicz_tpu.serving.engine import default_buckets
            self.buckets = default_buckets(self.max_batch)

    def bucket_for(self, rows):
        """The NOMINAL bucket for a ``rows``-row request — the
        smallest ladder entry >= rows, i.e. what the request pads
        into when dispatched alone (a coalescing batcher may ride it
        through a larger bucket).  Over-ladder rows clamp to the top
        bucket — the engine would have 400'd those, and they carry an
        error status anyway."""
        for b in self.buckets:
            if rows <= b:
                return b
        return self.buckets[-1]


def parse_priority_mix(spec):
    """``"high:1,normal:2,low:1"`` → ``[(name, weight), ...]``
    (sorted by name — a stable draw order so the tape is
    seed-deterministic regardless of spelling order).  Unknown lane
    names fail LOUDLY against the batcher's own vocabulary."""
    from znicz_tpu.serving.continuous import normalize_priority
    out = {}
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        name, sep, weight = part.partition(":")
        if not sep:
            raise ValueError(
                "priority mix wants PRIO:WEIGHT entries, got %r"
                % part)
        out[normalize_priority(name)] = float(weight)
    if not out:
        raise ValueError("empty priority mix %r" % spec)
    return sorted(out.items())


def make_plan(rate_rps, duration_s, seed, models, priority_mix=None):
    """The deterministic traffic tape: ``[(t, model_index, rows,
    priority)]`` sorted by arrival time ``t`` (seconds from start).
    Poisson arrivals at ``rate_rps``; the model is a weighted draw;
    ``rows`` is log-uniform over ``1..max_batch`` (every bucket sees
    traffic, small requests dominate — the realistic shape mix);
    ``priority`` is a weighted draw from ``priority_mix``
    (``[(name, weight), ...]`` or a ``"high:1,low:2"`` spec string) on
    a SEPARATE seeded stream — same-seed runs offer byte-identical
    traffic, and a run without a mix draws the exact tape it always
    drew (priority None = the server's "normal" default)."""
    rng = numpy.random.RandomState(int(seed))
    weights = numpy.array([m.weight for m in models], dtype=float)
    weights = weights / weights.sum()
    prio_names = prio_weights = prio_rng = None
    if priority_mix:
        if isinstance(priority_mix, str):
            priority_mix = parse_priority_mix(priority_mix)
        prio_names = [p for p, _ in priority_mix]
        prio_weights = numpy.array(
            [w for _, w in priority_mix], dtype=float)
        prio_weights = prio_weights / prio_weights.sum()
        # a dedicated stream: adding a mix must not perturb the
        # arrival/model/rows tape a seed has always produced
        prio_rng = numpy.random.RandomState(int(seed) + 2)
    plan = []
    t = float(rng.exponential(1.0 / rate_rps))
    while t < duration_s:
        mi = int(rng.choice(len(models), p=weights))
        # one octave past the ladder top, then clamp: the clamp mass
        # is what gives max_batch (the largest bucket) its share
        hi = math.log2(models[mi].max_batch) if \
            models[mi].max_batch > 1 else 0.0
        rows = int(2 ** rng.uniform(0.0, hi + 1.0))
        rows = max(1, min(rows, models[mi].max_batch))
        prio = None
        if prio_rng is not None:
            prio = prio_names[int(prio_rng.choice(
                len(prio_names), p=prio_weights))]
        plan.append((t, mi, rows, prio))
        t += float(rng.exponential(1.0 / rate_rps))
    return plan


def make_inputs(models, seed):
    """One ``(max_batch,) + sample_shape`` array per model (seeded);
    a request of ``rows`` rows is a leading slice — the generator
    measures the serving stack, not ``numpy.random``."""
    rng = numpy.random.RandomState(int(seed) + 1)
    return [rng.uniform(-1.0, 1.0, (m.max_batch,) + m.sample_shape)
            .astype(numpy.float32) for m in models]


def _percentile(values, q):
    """Exact quantile from the retained samples — ONE formula for the
    whole latency stack (znicz_tpu/serving/latency.py; unit-tested
    there down to n=1 and ties).  Imported lazily so the module stays
    importable before znicz_tpu's heavier imports are wanted."""
    from znicz_tpu.serving.latency import exact_percentile
    return exact_percentile(values, q)


def _pct_block(lat_s):
    """The per-series latency block: exact p50/p90/p95/p99/p999/max in
    ms over retained OK latencies (all None when the series is
    empty)."""
    # one real sort per series; exact_percentile's own sorted() is
    # O(n) on already-sorted input
    lat_s = sorted(lat_s)
    out = {}
    for label, q in (("p50", 50), ("p90", 90), ("p95", 95),
                     ("p99", 99), ("p999", 99.9)):
        v = _percentile(lat_s, q)
        out[label] = round(v * 1e3, 3) if v is not None else None
    out["max"] = round(max(lat_s) * 1e3, 3) if lat_s else None
    return out


def _classify(exc):
    """HTTP-status classification of a failure — in-process exceptions
    map exactly as the ServingServer's error handlers map them; HTTP
    errors carry their status verbatim."""
    from znicz_tpu.serving.batcher import (BatcherStoppedError,
                                           QueueFullError,
                                           RequestTimeoutError)
    from znicz_tpu.serving.breaker import CircuitOpenError
    from znicz_tpu.serving.registry import UnknownModelError
    if isinstance(exc, _HttpStatusError):
        return exc.code
    if isinstance(exc, QueueFullError):
        return 429
    if isinstance(exc, RequestTimeoutError):
        return 504
    if isinstance(exc, (CircuitOpenError, BatcherStoppedError)):
        return 503
    if isinstance(exc, UnknownModelError):
        return 404
    if isinstance(exc, (ValueError, TypeError)):
        return 400
    return 500


def run(plan, models, submit, slo_ms, duration_s, seed,
        timeout_ms=None, settle_s=30.0):
    """Fire ``plan`` open-loop through ``submit(model_name, x,
    timeout_ms) -> concurrent.futures.Future`` and return the SLO
    report.  Latency is measured from each request's SCHEDULED arrival
    — a dispatcher running late (server backpressure propagating into
    the client) counts against the request, exactly as a real user
    would experience it."""
    inputs = make_inputs(models, seed)
    lock = threading.Lock()
    # (model_index, rows, latency_s, status, priority, generation)
    records = []
    outstanding = threading.Semaphore(0)
    n_async = 0

    def _finish(rec_base, prio, scheduled_wall, future):
        done = time.monotonic()
        exc = future.exception()
        status = 200 if exc is None else _classify(exc)
        # HTTP submits resolve to the reply's X-Serving-Generation
        # label (which generation answered — the canary-split
        # evidence); in-process submits resolve to the output array,
        # which carries no attribution
        res = future.result() if exc is None else None
        gen = res if isinstance(res, str) else None
        with lock:
            records.append(rec_base + (done - scheduled_wall, status,
                                       prio, gen))
        outstanding.release()

    t0 = time.monotonic()
    behind_max = 0.0
    for t, mi, rows, prio in plan:
        scheduled_wall = t0 + t
        now = time.monotonic()
        if scheduled_wall > now:
            time.sleep(scheduled_wall - now)
        else:
            behind_max = max(behind_max, now - scheduled_wall)
        x = inputs[mi][:rows]
        try:
            future = submit(models[mi].name, x, timeout_ms, prio)
        except Exception as e:  # noqa: BLE001 - synchronous rejection
            with lock:
                records.append(
                    (mi, rows, time.monotonic() - scheduled_wall,
                     _classify(e), prio, None))
            continue
        n_async += 1
        future.add_done_callback(
            lambda f, rec=(mi, rows), p=prio, sw=scheduled_wall:
            _finish(rec, p, sw, f))
    deadline = time.monotonic() + settle_s
    for _ in range(n_async):
        if not outstanding.acquire(timeout=max(
                0.0, deadline - time.monotonic())):
            break
    wall_s = time.monotonic() - t0
    return report(records, len(plan), duration_s, slo_ms, seed,
                  models, behind_max, wall_s=wall_s)


def report(records, scheduled, duration_s, slo_ms, seed, models,
           dispatch_behind_max_s=0.0, wall_s=None):
    """Aggregate per-request records into the SLO report dict.

    ``achieved_rps``/``goodput_rps`` divide by the OFFERED window
    ``duration_s`` (the open-loop convention); ``wall_rps`` divides by
    the wall time to the LAST completion — under overload a backlog
    drains after the offered window closes, and wall_rps is the honest
    sustained-throughput number (use it to calibrate capacity)."""
    slo_s = float(slo_ms) / 1e3
    ok_lat = [r[2] for r in records if r[3] == 200]
    good = sum(1 for r in records if r[3] == 200 and r[2] <= slo_s)
    errors = {}
    for r in records:
        if r[3] != 200:
            errors[str(r[3])] = errors.get(str(r[3]), 0) + 1
    per_model = {}
    for i, m in enumerate(models):
        mine = [r for r in records if r[0] == i]
        m_ok = [r[2] for r in mine if r[3] == 200]
        m_pct = _pct_block(m_ok)
        per_bucket = {}
        for r in mine:
            if r[3] != 200:
                continue
            per_bucket.setdefault(m.bucket_for(r[1]), []).append(r[2])
        block = {
            "requests": len(mine),
            "ok": len(m_ok),
            "rows": int(sum(r[1] for r in mine)),
            # flat keys kept for existing consumers; the full exact-
            # quantile block sits under "latency_ms"
            "p50_ms": m_pct["p50"],
            "p99_ms": m_pct["p99"],
            "latency_ms": m_pct,
            # per NOMINAL shape bucket (what the request's own rows
            # pad into; coalescing may dispatch some through a larger
            # bucket — this is the client-side "tail by request size"
            # view): a p99 regression on one request class can no
            # longer hide in the model-wide aggregate
            "per_bucket": {
                str(b): dict(_pct_block(lats), count=len(lats))
                for b, lats in sorted(per_bucket.items())},
        }
        per_model[m.name or "<default>"] = block
    # per-priority breakdown (the overload contract's evidence):
    # goodput and the latency tail per lane — under overload the low
    # lane should show 429s where the high lane shows green goodput
    per_priority = {}
    prios = sorted({r[4] for r in records if len(r) > 4 and r[4]})
    for prio in prios:
        mine = [r for r in records if r[4] == prio]
        p_ok = [r[2] for r in mine if r[3] == 200]
        p_good = sum(1 for r in mine
                     if r[3] == 200 and r[2] <= slo_s)
        p_errors = {}
        for r in mine:
            if r[3] != 200:
                p_errors[str(r[3])] = p_errors.get(str(r[3]), 0) + 1
        per_priority[prio] = {
            "requests": len(mine),
            "ok": len(p_ok),
            "errors": p_errors,
            "shed_429": p_errors.get("429", 0),
            "goodput_pct": (round(100.0 * p_good / len(mine), 2)
                            if mine else None),
            "latency_ms": _pct_block(p_ok),
        }
    # per-generation breakdown (the release plane's client-side
    # evidence): each HTTP reply names the generation that answered
    # it in X-Serving-Generation — during a canary the share_pct here
    # IS the observed split, so a release run can assert the ladder
    # percentage from outside the fleet
    per_generation = {}
    gens = sorted({r[5] for r in records if len(r) > 5 and r[5]})
    for gen in gens:
        mine = [r for r in records if len(r) > 5 and r[5] == gen]
        g_ok = [r[2] for r in mine if r[3] == 200]
        g_good = sum(1 for r in mine
                     if r[3] == 200 and r[2] <= slo_s)
        per_generation[gen] = {
            "requests": len(mine),
            "ok": len(g_ok),
            "share_pct": (round(100.0 * len(mine) / len(records), 2)
                          if records else None),
            "goodput_pct": (round(100.0 * g_good / len(mine), 2)
                            if mine else None),
            "latency_ms": _pct_block(g_ok),
        }
    out = {
        "seed": int(seed),
        "duration_s": round(float(duration_s), 3),
        "slo_ms": float(slo_ms),
        "scheduled": int(scheduled),
        "completed": len(records),
        "ok": len(ok_lat),
        "errors": errors,
        "offered_rps": round(scheduled / duration_s, 2),
        "achieved_rps": round(len(ok_lat) / duration_s, 2),
        "wall_s": (round(wall_s, 3) if wall_s else None),
        "wall_rps": (round(len(ok_lat) / wall_s, 2)
                     if wall_s else None),
        "goodput_rps": round(good / duration_s, 2),
        "goodput_pct": (round(100.0 * good / scheduled, 2)
                        if scheduled else None),
        "latency_ms": _pct_block(ok_lat),
        "rows_ok": int(sum(r[1] for r in records if r[3] == 200)),
        "dispatch_behind_max_ms": round(
            dispatch_behind_max_s * 1e3, 3),
        "per_model": per_model,
        "per_priority": per_priority,
        "per_generation": per_generation,
    }
    return out


# -- HTTP mode -------------------------------------------------------------
class DaemonPool(object):
    """Minimal fixed-width thread pool over DAEMON threads returning
    Futures.  concurrent.futures' ThreadPoolExecutor joins its
    non-daemon workers at interpreter exit — a wedged server would
    hang the CLI for the full HTTP timeout after the report printed.
    Daemon workers let the process exit the moment main() returns."""

    def __init__(self, max_workers):
        import queue
        self._q = queue.Queue()
        for i in range(int(max_workers)):
            t = threading.Thread(target=self._worker,
                                 name="znicz:loadgen-%d" % i,
                                 daemon=True)
            t.start()

    def _worker(self):
        while True:
            fn, args, future = self._q.get()
            if not future.set_running_or_notify_cancel():
                continue
            try:
                future.set_result(fn(*args))
            except BaseException as e:  # noqa: BLE001 - to the future
                future.set_exception(e)

    def submit(self, fn, *args):
        from concurrent.futures import Future
        future = Future()
        self._q.put((fn, args, future))
        return future


def discover_models(base_url, timeout=10.0):
    """ModelSpecs from a live server's ``GET /models`` (the registry
    stats payload).  A single-engine server reports one pseudo-model
    named ``default`` — route it without a path segment."""
    import urllib.request
    with urllib.request.urlopen(base_url.rstrip("/") + "/models",
                                timeout=timeout) as resp:
        doc = json.loads(resp.read())
    specs = []
    for name in sorted(doc.get("models", {})):
        stats = doc["models"][name]
        shape = stats.get("sample_shape")
        if not shape:
            continue
        buckets = stats.get("buckets") or [8]
        specs.append(ModelSpec(
            None if name == "default" else name, shape,
            max_batch=int(buckets[-1]), buckets=buckets))
    if not specs:
        raise SystemExit(
            "loadgen: %s/models reports no servable model with a "
            "recorded sample shape" % base_url)
    return specs


def http_submit(base_url, pool, binary=False, rid_prefix=None):
    """A ``submit(model, x, timeout_ms) -> Future`` over HTTP: each
    request runs on the pool (open-loop up to the pool width; a full
    pool shows up as scheduled-latency, never as a lost arrival).

    ``rid_prefix`` stamps every request with a deterministic
    ``X-Request-Id`` (``<prefix>-<seq>``) so a caller can look
    sampled requests up afterwards at ``GET /debug/trace/<rid>`` —
    the fleet-tracing smoke drives loadgen traffic and then reads
    the stitched trees back by these ids.

    ``binary=True`` posts raw ``.npy`` bodies instead of JSON (the
    server's ``application/octet-stream`` path) over per-worker
    KEEP-ALIVE connections, and caches the encoded bytes per
    ``(model, rows)`` — the generator's inputs are fixed seeded
    slices, so the cache is exact.  JSON over one-shot connections
    costs ~3 ms of client GIL to encode, ~1.6 ms of server GIL to
    decode and a TCP handshake per 784-wide request; the binary path
    costs microseconds — at fleet scale the codec tax becomes the
    measurement, not the fleet.  (A binary body carries no
    per-request ``timeout_ms``; a request failing on a stale parked
    connection retries once on a fresh one.)"""
    import http.client
    import io
    import itertools
    import urllib.error
    import urllib.parse
    import urllib.request

    npy_cache = {}
    parsed = urllib.parse.urlsplit(base_url)
    local = threading.local()
    rid_seq = itertools.count()  # count() is atomic under the GIL

    def _body(model, x, timeout_ms):
        if not binary:
            doc = {"inputs": x.tolist()}
            if timeout_ms:
                doc["timeout_ms"] = timeout_ms
            return json.dumps(doc).encode(), "application/json"
        key = (model, x.shape[0])
        body = npy_cache.get(key)
        if body is None:
            buf = io.BytesIO()
            numpy.save(buf, numpy.ascontiguousarray(x))
            body = npy_cache[key] = buf.getvalue()
        return body, "application/octet-stream"

    def _do_binary(path, body, headers, wait):
        for attempt in (0, 1):
            conn = getattr(local, "conn", None)
            if conn is None:
                conn = http.client.HTTPConnection(
                    parsed.hostname, parsed.port, timeout=wait)
                local.conn = conn
            try:
                conn.request("POST", path, body=body,
                             headers=headers)
                resp = conn.getresponse()
                resp.read()
            except (OSError, http.client.HTTPException):
                conn.close()
                local.conn = None
                if attempt:
                    raise
                continue  # stale parked connection: one fresh retry
            if resp.will_close:
                conn.close()
                local.conn = None
            if resp.status >= 400:
                raise _HttpStatusError(resp.status)
            return resp.getheader("X-Serving-Generation") or True

    def _do(model, x, timeout_ms, priority):
        path = "/predict" if model is None else "/predict/" + model
        body, ctype = _body(model, x, timeout_ms)
        headers = {"Content-Type": ctype}
        if rid_prefix:
            headers["X-Request-Id"] = "%s-%06d" % (rid_prefix,
                                                   next(rid_seq))
        if priority is not None:
            headers["X-Priority"] = priority
        wait = (timeout_ms / 1e3 + 65.0) if timeout_ms else 120.0
        if binary:
            return _do_binary(path, body, headers, wait)
        req = urllib.request.Request(
            base_url.rstrip("/") + path, body, headers)
        try:
            with urllib.request.urlopen(req, timeout=wait) as resp:
                json.loads(resp.read())
                gen = resp.headers.get("X-Serving-Generation")
        except urllib.error.HTTPError as e:
            e.read()
            raise _HttpStatusError(e.code)
        return gen or True

    def submit(model, x, timeout_ms, priority=None):
        return pool.submit(_do, model, x, timeout_ms, priority)

    return submit


def discover_wire_port(base_url, timeout=10.0):
    """The server's framed-relay port from ``GET /healthz`` (both the
    replica and the fleet router publish ``wire_port`` there).  A
    not-ready 503 still carries the payload."""
    import urllib.error
    import urllib.request
    url = base_url.rstrip("/") + "/healthz"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            doc = json.loads(resp.read())
    except urllib.error.HTTPError as e:
        doc = json.loads(e.read())
    port = doc.get("wire_port")
    if not port:
        raise SystemExit(
            "loadgen: %s reports no wire_port — the server runs "
            "with common.serving.wire.enabled=False; use --wire "
            "http" % url)
    return int(port)


def wire_submit(base_url, pool, rid_prefix=None):
    """A ``submit(model, x, timeout_ms) -> Future`` over the binary
    framed relay (serving/wire.py) — the client half of ``--wire
    binary``.  The traffic is seed-identical to the HTTP modes (same
    plan, same seeded input slices); only the transport differs:
    one persistent connection per pool worker, a length-prefixed
    REQUEST frame per request (rid/model/priority/timeout_ms in the
    frame meta, the raw ``.npy`` body cached per ``(model, rows)``
    exactly as ``--npy`` caches it), and the RESPONSE frame's
    ``generation`` meta resolving the future — the same per-
    generation attribution the HTTP header carries.  A request
    failing on a stale parked connection retries once on a fresh
    one; a typed ERROR frame raises its carried status verbatim."""
    import io
    import itertools
    import urllib.parse

    from znicz_tpu.serving import wire

    parsed = urllib.parse.urlsplit(base_url)
    port = discover_wire_port(base_url)
    npy_cache = {}
    local = threading.local()
    rid_seq = itertools.count()  # count() is atomic under the GIL

    def _body(model, x):
        key = (model, x.shape[0])
        body = npy_cache.get(key)
        if body is None:
            buf = io.BytesIO()
            numpy.save(buf, numpy.ascontiguousarray(x))
            body = npy_cache[key] = buf.getvalue()
        return body

    def _do(model, x, timeout_ms, priority):
        body = _body(model, x)
        meta = {"rid": "%s-%06d" % (rid_prefix or "wire",
                                    next(rid_seq))}
        if model is not None:
            meta["model"] = model
        if priority is not None:
            meta["priority"] = priority
        if timeout_ms:
            meta["timeout_ms"] = timeout_ms
        wait = (timeout_ms / 1e3 + 65.0) if timeout_ms else 120.0
        for attempt in (0, 1):
            conn = getattr(local, "conn", None)
            if conn is None:
                conn = wire.WireConn(parsed.hostname, port,
                                     timeout=wait)
                local.conn = conn
            try:
                kind, rmeta, _rbody = conn.request(meta, body,
                                                   timeout=wait)
            except (wire.WireProtocolError, OSError):
                conn.close()
                local.conn = None
                if attempt:
                    raise
                continue  # stale parked connection: one fresh retry
            status = int(rmeta.get("status", 500))
            if status >= 400:
                raise _HttpStatusError(status)
            return rmeta.get("generation") or True

    def submit(model, x, timeout_ms, priority=None):
        return pool.submit(_do, model, x, timeout_ms, priority)

    return submit


class _HttpStatusError(Exception):
    def __init__(self, code):
        self.code = int(code)
        super(_HttpStatusError, self).__init__("HTTP %d" % code)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python tools/loadgen.py",
        description="Open-loop (Poisson) load generator against a "
                    "znicz_tpu serving server; prints the SLO report "
                    "as one JSON line.")
    parser.add_argument("url", help="server base url, e.g. "
                                    "http://127.0.0.1:8899")
    parser.add_argument("--rate", type=float, default=100.0,
                        help="offered arrivals per second")
    parser.add_argument("--duration", type=float, default=10.0)
    parser.add_argument("--seed", type=int, default=7)
    parser.add_argument("--slo-ms", type=float, default=None,
                        help="goodput latency bound (default: "
                             "root.common.serving.slo_ms)")
    parser.add_argument("--timeout-ms", type=float, default=None,
                        help="per-request deadline forwarded to the "
                             "server")
    parser.add_argument("--models", default=None,
                        help="comma list restricting the discovered "
                             "fleet (default: every servable model)")
    parser.add_argument("--concurrency", type=int, default=64,
                        help="HTTP worker pool width (the open-loop "
                             "outstanding-request bound)")
    parser.add_argument("--npy", action="store_true",
                        help="post raw .npy bodies instead of JSON "
                             "(microseconds of codec per request "
                             "instead of milliseconds — use for "
                             "capacity/fleet-scaling measurements; "
                             "note: per-request timeout_ms does not "
                             "ride in a binary body)")
    parser.add_argument("--wire", default="http",
                        choices=("http", "binary"),
                        help="client transport: 'http' (default; "
                             "--npy picks the body codec) or "
                             "'binary' — the persistent framed "
                             "relay (serving/wire.py) the router "
                             "itself speaks to replicas, with rid/"
                             "model/priority/timeout_ms in the "
                             "frame meta.  Same seed = byte-"
                             "identical traffic either way; only "
                             "the transport differs")
    parser.add_argument("--priority-mix", default=None,
                        metavar="PRIO:W[,PRIO:W...]",
                        help="weighted per-request priority draw "
                             "(e.g. 'high:1,normal:2,low:1'), on a "
                             "dedicated seeded stream — the report "
                             "then carries per-priority goodput/"
                             "latency blocks")
    parser.add_argument("--assert-goodput-pct", default=None,
                        metavar="PCT|PRIO:PCT[,...]",
                        help="exit 1 when goodput%% lands below this "
                             "(the CI SLO assertion).  A bare number "
                             "gates the GLOBAL goodput; a PRIO:PCT "
                             "entry gates that priority lane's "
                             "goodput (e.g. 'high:90' holds the "
                             "high lane under overload); comma-"
                             "separate to gate several")
    parser.add_argument("--assert-goodput-gap", default=None,
                        metavar="PRIO:PRIO:PTS[,...]",
                        help="exit 1 when lane A's goodput%% does not "
                             "exceed lane B's by at least PTS points "
                             "(e.g. 'high:low:10').  Gates the "
                             "RELATIVE overload contract — robust on "
                             "slow machines where every absolute "
                             "goodput number sags together")
    args = parser.parse_args(argv)

    from znicz_tpu.core.config import root
    slo_ms = (args.slo_ms if args.slo_ms is not None
              else float(root.common.serving.get("slo_ms", 100.0)))
    models = discover_models(args.url)
    if args.models:
        want = {m.strip() for m in args.models.split(",")}
        models = [m for m in models if (m.name or "default") in want]
        if not models:
            parser.error("--models %r matched nothing" % args.models)
    plan = make_plan(args.rate, args.duration, args.seed, models,
                     priority_mix=args.priority_mix)
    pool = DaemonPool(args.concurrency)
    if args.wire == "binary":
        submit = wire_submit(args.url, pool)
    else:
        submit = http_submit(args.url, pool, binary=args.npy)
    out = run(plan, models, submit, slo_ms,
              args.duration, args.seed, timeout_ms=args.timeout_ms)
    out["url"] = args.url
    out["wire"] = args.wire
    out["models"] = [m.name or "<default>" for m in models]
    print(json.dumps(out))
    if args.assert_goodput_pct is not None:
        failed = []
        for entry in str(args.assert_goodput_pct).split(","):
            entry = entry.strip()
            if not entry:
                continue
            prio, sep, pct = entry.rpartition(":")
            want = float(pct if sep else entry)
            if sep:
                block = out["per_priority"].get(prio)
                if block is None:
                    failed.append(
                        "%s: no %r traffic in the report (run with "
                        "--priority-mix including it)" % (entry,
                                                          prio))
                    continue
                got = block["goodput_pct"] or 0.0
                label = "%s-priority goodput" % prio
            else:
                got = out["goodput_pct"] or 0.0
                label = "goodput"
            if got < want:
                failed.append("%s %.2f%% below the %.2f%% SLO "
                              "assertion" % (label, got, want))
        if failed:
            for line in failed:
                print("loadgen: " + line, file=sys.stderr)
            return 1
    if args.assert_goodput_gap is not None:
        failed = []
        for entry in str(args.assert_goodput_gap).split(","):
            entry = entry.strip()
            if not entry:
                continue
            try:
                hi, lo, pts = entry.split(":")
                pts = float(pts)
            except ValueError:
                parser.error("--assert-goodput-gap wants "
                             "PRIO:PRIO:PTS, got %r" % entry)
            blocks = out["per_priority"]
            missing = [p for p in (hi, lo) if p not in blocks]
            if missing:
                failed.append(
                    "%s: no %s traffic in the report (run with "
                    "--priority-mix including it)"
                    % (entry, "/".join(missing)))
                continue
            got_hi = blocks[hi]["goodput_pct"] or 0.0
            got_lo = blocks[lo]["goodput_pct"] or 0.0
            if got_hi - got_lo < pts:
                failed.append(
                    "%s-vs-%s goodput gap %.2f points below the "
                    "%.2f-point assertion (%s=%.2f%%, %s=%.2f%%)"
                    % (hi, lo, got_hi - got_lo, pts, hi, got_hi,
                       lo, got_lo))
        if failed:
            for line in failed:
                print("loadgen: " + line, file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
