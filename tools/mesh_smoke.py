"""CI smoke: mesh-sharded asynchronous fused training end to end — the
wine fused config trained on a 1-device and a 4-device (data-parallel)
mesh over forced virtual CPU host devices, asserting the sharded
control-plane contract (ISSUE 6):

* identical final decision aggregates: per-epoch error integers and the
  confusion matrix EXACT, max_err_output_sum EXACT (the shard fold is a
  max — reduction-order independent),
* the one-readback-per-segment invariant SURVIVES sharding:
  ``trainer.readbacks == segments`` and telemetry ``d2h_calls ==
  segments`` on the 4-shard run, exactly like the 1-device run,
* the telemetry summary reports the mesh extents
  (``data_shards``/``model_shards``) the run executed under.

Run by ``tools/ci.sh`` (fast lane).  Exit code 0 = pass.
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# the virtual device count must be forced BEFORE jax initializes a
# backend (same recipe as tests/conftest.py)
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=4").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy  # noqa: E402

from znicz_tpu.core.config import root  # noqa: E402
from znicz_tpu.core import prng, telemetry  # noqa: E402
from znicz_tpu.core.backends import JaxDevice  # noqa: E402

EPOCHS = 3
WINDOW = 4
MB = 16  # wine: 178 samples -> 12 minibatches; divisible by 4 shards

LAYERS = [
    {"type": "all2all_tanh", "->": {"output_sample_shape": 8},
     "<-": {"learning_rate": 0.1}},
    {"type": "softmax", "->": {"output_sample_shape": 3},
     "<-": {"learning_rate": 0.1}},
]


def run(fused_cfg):
    import znicz_tpu.loader.loader_wine  # noqa: F401
    from znicz_tpu.standard_workflow import StandardWorkflow
    telemetry.reset()
    prng.get(1).seed(1234)
    prng.get(2).seed(5678)
    wf = StandardWorkflow(
        None, layers=[dict(l) for l in LAYERS],
        loader_name="wine_loader",
        loader_config={"minibatch_size": MB},
        decision_config={"max_epochs": EPOCHS, "fail_iterations": 100},
        snapshotter_config={"prefix": "msmoke", "interval": 10 ** 9,
                            "time_interval": 1e9, "compression": ""},
        fused=dict({"window": WINDOW}, **fused_cfg))
    wf.initialize(device=JaxDevice())
    wf.run()
    return wf, telemetry.summary()


def main():
    tmp = tempfile.mkdtemp(prefix="mesh_smoke_")
    root.common.dirs.snapshots = os.path.join(tmp, "snapshots")
    telemetry.enable()

    wf_1, tele_1 = run({})
    wf_4, tele_4 = run({"mesh": 4})

    assert wf_4.fused_trainer.net.data_shards == 4
    assert tele_4.get("data_shards") == 4, tele_4

    # identical integer aggregates + the exact max fold
    assert list(wf_1.decision.epoch_n_err) == \
        list(wf_4.decision.epoch_n_err), \
        (wf_1.decision.epoch_n_err, wf_4.decision.epoch_n_err)
    for ca, cb in zip(wf_1.decision.confusion_matrixes,
                      wf_4.decision.confusion_matrixes):
        if ca is None or cb is None:
            assert ca is None and cb is None
            continue
        numpy.testing.assert_array_equal(ca, cb)
    assert wf_1.decision.max_err_y_sums == wf_4.decision.max_err_y_sums

    # parameters: the gradient psum reassociates the same f32 batch sum
    for la, lb in zip(wf_1.fused_trainer.host_params(),
                      wf_4.fused_trainer.host_params()):
        for k in la:
            numpy.testing.assert_allclose(la[k], lb[k], rtol=1e-5,
                                          atol=1e-6)

    # the PR 5 invariant survives sharding: one readback per segment on
    # BOTH runs (wine has a single TRAIN segment per epoch)
    segments = EPOCHS
    assert tele_1.get("readbacks") == segments, tele_1
    assert tele_4.get("readbacks") == segments, tele_4
    assert tele_4.get("d2h_calls") == segments, tele_4

    print("mesh smoke OK: %d epochs, 1-dev vs 4-shard aggregates "
          "identical, readbacks %d==%d (1/segment), d2h calls %d, "
          "d2h %d B vs %d B per run"
          % (EPOCHS, tele_1["readbacks"], tele_4["readbacks"],
             tele_4["d2h_calls"], tele_1["d2h_bytes"],
             tele_4["d2h_bytes"]))


if __name__ == "__main__":
    main()
