#!/usr/bin/env python3
"""graftlint CLI — project-invariant static analysis for the repo.

The one lint CI runs (subsumes the retired style-only ``tools/lint.py``
— its checks are folded in as the ``syntax``/``tabs``/
``trailing-whitespace``/``line-length``/``unused-import``/
``bare-except``/``library-print`` family).  The project-invariant
checkers and their rationale live in
:mod:`znicz_tpu.analysis.graftlint`; the catalog is documented in
``docs/development.md``.

Usage::

    python tools/graftlint.py              # scan; exit 1 on findings
                                           # outside the baseline
    python tools/graftlint.py --selftest   # every checker must reject
                                           # its seeded violation and
                                           # pass its clean twin
    python tools/graftlint.py --write-baseline   # regenerate the
                                           # reviewed exception file

The baseline (``tools/graftlint_baseline.txt``) holds reviewed
``path :: check :: token`` fingerprints; a finding matching one is
suppressed, and stale entries are reported so the file stays honest.
Dependency-free: imports only ``znicz_tpu.analysis.graftlint`` and
``znicz_tpu.core.config`` (no jax).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from znicz_tpu.analysis import graftlint  # noqa: E402

DEFAULT_BASELINE = os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "graftlint_baseline.txt")


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--selftest", action="store_true",
                        help="prove each checker rejects its seeded "
                             "violation and passes its clean twin")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE,
                        help="reviewed-exception fingerprint file "
                             "(default: %(default)s)")
    parser.add_argument("--write-baseline", action="store_true",
                        help="rewrite the baseline from the current "
                             "findings (review the diff!)")
    args = parser.parse_args(argv)

    if args.selftest:
        problems = graftlint.selftest()
        for p in problems:
            print("SELFTEST FAIL: %s" % p)
        if problems:
            return 1
        print("graftlint selftest: %d checkers rejected their seeded "
              "violation and passed their clean twin"
              % len(graftlint.FIXTURES))
        return 0

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    findings = graftlint.run(root)

    if args.write_baseline:
        with open(args.baseline, "w", encoding="utf-8") as f:
            f.write("# graftlint reviewed exceptions — one\n"
                    "# 'path :: check :: token' fingerprint per "
                    "line.\n# Regenerate with --write-baseline; "
                    "every entry needs a review.\n")
            for fp in sorted(set(x.fingerprint for x in findings)):
                f.write(fp + "\n")
        print("baseline: %d entr%s -> %s"
              % (len(findings), "y" if len(findings) == 1 else "ies",
                 args.baseline))
        return 0

    baseline = graftlint.load_baseline(args.baseline)
    kept, suppressed, stale = graftlint.apply_baseline(findings,
                                                       baseline)
    for f in kept:
        print(f)
    for fp in stale:
        print("stale baseline entry (no longer matches — remove it): "
              "%s" % fp)
    if kept:
        print("%d problem(s)%s" % (
            len(kept),
            " (+%d baselined)" % len(suppressed)
            if suppressed else ""))
        return 1
    print("graftlint clean%s%s" % (
        " (%d baselined exception%s)" % (
            len(suppressed), "" if len(suppressed) == 1 else "s")
        if suppressed else "",
        "; %d stale baseline entr%s" % (
            len(stale), "y" if len(stale) == 1 else "ies")
        if stale else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())
