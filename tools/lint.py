#!/usr/bin/env python3
"""Dependency-free lint for the repo (the reference ships flake8/pylint
configs; this box has neither, so the checks are implemented directly).

Checks: syntax, tabs in indentation, trailing whitespace, line length,
unused imports (per module, `# noqa` opt-out), bare except, and
`print(` calls inside the library (samples/CLI excluded).
"""

import ast
import os
import sys

MAX_LINE = 80
LIB_DIRS = ("znicz_tpu",)
SCAN_DIRS = ("znicz_tpu", "tests", "tools")
SKIP_PARTS = ("__pycache__",)
PRINT_OK = ("samples", "__main__.py", "launcher.py", "parity.py")


def iter_py(root):
    for base in SCAN_DIRS:
        for dirpath, dirnames, filenames in os.walk(
                os.path.join(root, base)):
            if any(p in dirpath for p in SKIP_PARTS):
                continue
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def unused_imports(tree, source_lines):
    imported = {}  # name -> (lineno, as_what)
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                name = alias.asname or alias.name.split(".")[0]
                imported[name] = node.lineno
        elif isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if alias.name == "*":
                    continue
                name = alias.asname or alias.name
                imported[name] = node.lineno
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            n = node
            while isinstance(n, ast.Attribute):
                n = n.value
            if isinstance(n, ast.Name):
                used.add(n.id)
    out = []
    for name, lineno in imported.items():
        if name in used:
            continue
        line = source_lines[lineno - 1] if lineno <= len(source_lines) \
            else ""
        if "noqa" in line:
            continue
        out.append((lineno, "unused import %r" % name))
    return out


def check_file(path, rel):
    problems = []
    with open(path, encoding="utf-8") as f:
        src = f.read()
    lines = src.splitlines()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [(e.lineno or 0, "syntax error: %s" % e.msg)]
    for i, line in enumerate(lines, 1):
        stripped = line.rstrip("\n")
        indent = stripped[:len(stripped) - len(stripped.lstrip())]
        if "\t" in indent:
            problems.append((i, "tab in indentation"))
        if stripped != stripped.rstrip():
            problems.append((i, "trailing whitespace"))
        if len(stripped) > MAX_LINE and "noqa" not in stripped:
            problems.append((i, "line too long (%d > %d)"
                             % (len(stripped), MAX_LINE)))
    problems.extend(unused_imports(tree, lines))
    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            problems.append((node.lineno, "bare except"))
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"
                and rel.startswith(LIB_DIRS)
                and not any(p in rel for p in PRINT_OK)
                and "noqa" not in lines[node.lineno - 1]):
            problems.append((node.lineno,
                             "print() in library code (use the logger)"))
    return problems


def main():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    total = 0
    for path in iter_py(root):
        rel = os.path.relpath(path, root)
        for lineno, msg in sorted(check_file(path, rel)):
            print("%s:%d: %s" % (rel, lineno, msg))
            total += 1
    if total:
        print("%d problem(s)" % total)
        return 1
    print("lint clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
