#!/usr/bin/env python3
"""Retired — the style checks moved into ``tools/graftlint.py``
(ISSUE 13), which adds the project-invariant checkers on top.  This
shim keeps ``python tools/lint.py`` working for muscle memory and old
scripts by delegating to the graftlint CLI.
"""

import sys

if __name__ == "__main__":
    sys.stderr.write("tools/lint.py is retired; running "
                     "tools/graftlint.py (see docs/development.md)\n")
    from graftlint import main
    sys.exit(main())
