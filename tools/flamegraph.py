"""Render a continuous-profiler capture into flamegraph inputs.

Usage::

    python tools/flamegraph.py <url|file>                # speedscope
    python tools/flamegraph.py <url|file> -o prof.json   # to a file
    python tools/flamegraph.py <url|file> --collapsed    # folded text

The input is a ``GET /debug/pyprof`` payload (``core/pyprof.py``) —
a saved JSON file, or an ``http(s)://`` URL fetched live.  Point it
at a replica for one process, or at the fleet router for the stitched
fleet-merged profile.

Outputs:

* default — a standalone speedscope-importable JSON document
  (https://www.speedscope.app: drag the file in, or ``speedscope
  prof.json``); sample counts become weights, component is the root
  frame of every stack so the fleet view groups by component;
* ``--collapsed`` — Brendan-Gregg folded-stack text
  (``component;frame;...;leaf count`` per line), the input format of
  ``flamegraph.pl`` and most flamegraph tooling.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# the renderers live next to the sampler so the HTTP endpoint and
# this CLI can never drift apart on the format
from znicz_tpu.core import pyprof  # noqa: E402


def _load(source):
    if str(source).startswith(("http://", "https://")):
        import urllib.request
        with urllib.request.urlopen(source, timeout=60) as resp:
            return json.loads(resp.read())
    with open(source) as f:
        return json.load(f)


def main(argv):
    args = [a for a in argv if not a.startswith("-")]
    if not args:
        raise SystemExit(__doc__)
    out_path = None
    if "-o" in argv:
        out_path = argv[argv.index("-o") + 1]
        args = [a for a in args if a != out_path]
    prof = _load(args[0])
    if not prof.get("stacks"):
        raise SystemExit(
            "no stacks in %s (profiler disabled, or an empty capture "
            "window — arm root.common.profiler.pyprof.enabled and "
            "put load on the server)" % args[0])
    if "--collapsed" in argv:
        text = pyprof.collapsed(prof) + "\n"
    else:
        text = json.dumps(pyprof.speedscope(
            prof, name="pyprof:%s" % args[0]), indent=1) + "\n"
    if out_path:
        with open(out_path, "w") as f:
            f.write(text)
        print("wrote %s (%d samples, %d stacks)"
              % (out_path, prof.get("samples", 0),
                 len(prof["stacks"])))
    else:
        sys.stdout.write(text)


if __name__ == "__main__":
    main(sys.argv[1:])
