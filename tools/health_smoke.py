"""CI smoke: the active-observability layer end to end — train a tiny
wine model with the numeric health monitor armed (``policy=halt``,
``interval=1``), inject NaN weights after the first epoch, and assert
the acceptance contract of the health subsystem:

* the monitor trips on the first training step that produces NaN
  gradients and raises the typed :class:`HealthViolationError`,
* a crash report exists on disk with the last journal events
  (``events.jsonl``), a metrics snapshot (``metrics.json``) and the
  report metadata,
* the journal records the violation (``health.violation`` event) and
  ``tools/profile_summary.py --journal`` renders the timeline with the
  violation highlighted,
* ``GET /debug/health`` on the status server reports the violation
  (healthz-style 503).

Run by ``tools/ci.sh`` (fast lane).  Exit code 0 = pass.
"""

import json
import os
import sys
import tempfile
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy  # noqa: E402

from znicz_tpu.core.config import root  # noqa: E402
from znicz_tpu.core import health, prng, telemetry  # noqa: E402
from znicz_tpu.core.status_server import StatusServer  # noqa: E402


def main():
    tmp = tempfile.mkdtemp(prefix="health_smoke_")
    root.common.dirs.snapshots = os.path.join(tmp, "snapshots")
    root.common.health.crash_dir = os.path.join(tmp, "crash")
    telemetry.enable()
    telemetry.reset()
    health.reset()
    health.enable(policy="halt", interval=1)

    import znicz_tpu.loader.loader_wine  # noqa: F401
    from znicz_tpu.standard_workflow import StandardWorkflow
    prng.get(1).seed(1024)
    prng.get(2).seed(1025)
    wf = StandardWorkflow(
        None,
        layers=[
            {"type": "all2all_tanh", "->": {"output_sample_shape": 8},
             "<-": {"learning_rate": 0.1}},
            {"type": "softmax", "->": {"output_sample_shape": 3},
             "<-": {"learning_rate": 0.1}},
        ],
        loader_name="wine_loader",
        loader_config={"minibatch_size": 10},
        decision_config={"max_epochs": 5, "fail_iterations": 20},
        snapshotter_config={"prefix": "hsmoke", "interval": 10 ** 9,
                            "time_interval": 1e9, "compression": ""})
    wf.initialize()

    # poison the first layer's weights at the end of train epoch 1 —
    # the NEXT train step's gradients go NaN and the monitor must trip
    # on that step (policy=halt raises the typed error)
    orig_hook = wf.decision.on_training_finished
    poisoned = []

    def poison():
        orig_hook()
        if not poisoned:
            poisoned.append(int(wf.decision.epoch_number))
            wf.forwards[0].weights.map_write()
            wf.forwards[0].weights.mem[0, 0] = numpy.nan

    wf.decision.on_training_finished = poison

    try:
        wf.run()
    except health.HealthViolationError as e:
        violation = e
    else:
        raise AssertionError("health monitor never tripped on NaN")

    assert "NaN" in str(violation), violation
    assert violation.crash_report and \
        os.path.isdir(violation.crash_report), violation.crash_report
    for fname in ("events.jsonl", "metrics.json", "report.json"):
        path = os.path.join(violation.crash_report, fname)
        assert os.path.isfile(path), "crash report missing %s" % fname

    # the journal recorded the violation and the crash report holds it
    kinds = [ev["kind"] for ev in telemetry.journal_events()]
    assert "health.violation" in kinds, kinds
    assert "config" in kinds and "train.epoch" in kinds, kinds
    events_path = os.path.join(violation.crash_report, "events.jsonl")
    with open(events_path) as f:
        dumped = [json.loads(line) for line in f if line.strip()]
    assert any(ev["kind"] == "health.violation" for ev in dumped)

    # metrics snapshot carries the health counters
    with open(os.path.join(violation.crash_report, "metrics.json")) as f:
        metrics = json.load(f)
    assert metrics["counters"].get("health.violations", 0) >= 1
    assert metrics["counters"].get("health.checks", 0) >= 1

    # --journal timeline renders, violation highlighted
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import profile_summary
    table = profile_summary.summarize_journal(events_path)
    assert "!!" in table and "health.violation" in table

    # /debug/health answers healthz-style: 503 with the violation
    server = StatusServer(wf, port=0).start()
    try:
        url = "http://127.0.0.1:%d/debug/health" % server.port
        try:
            urllib.request.urlopen(url, timeout=10)
            raise AssertionError("/debug/health returned 200 after a "
                                 "violation")
        except urllib.error.HTTPError as e:
            assert e.code == 503
            doc = json.loads(e.read())
        assert doc["violations"] >= 1 and not doc["ok"]
        assert doc["last_violation"]["reason"] == str(violation)
        with urllib.request.urlopen(
                "http://127.0.0.1:%d/debug/events" % server.port,
                timeout=10) as r:
            events_doc = json.loads(r.read())
        assert any(ev["kind"] == "health.violation"
                   for ev in events_doc["events"])
    finally:
        server.stop()

    status = health.status()
    print("health smoke OK: tripped on epoch %d (%s), crash report "
          "%s (%d journal events, %d checks)"
          % (poisoned[0] + 1, violation, violation.crash_report,
             len(dumped), status["checks"]))


if __name__ == "__main__":
    main()
