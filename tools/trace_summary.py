"""Critical-path analysis over sampled request trace trees.

Usage::

    python tools/trace_summary.py http://127.0.0.1:8899 [--top N]
    python tools/trace_summary.py tree.json [more.json ...] [--json]

Input is either a LIVE server base URL — the tool walks
``GET /debug/trace`` for the sampled rids and fetches every tree
(against a fleet router that means STITCHED cross-process trees,
serving/router.py PR 16) — or saved ``/debug/trace/<rid>`` JSON
payloads (a file may hold one tree or a list of trees).

The report answers the two questions an operator asks a trace ring:

* **where does time go, fleet-wide?** — per-span-kind count / p50 /
  p99 / total milliseconds, top-level kinds only (nested kinds like
  ``device`` / ``replica`` ride inside their parents and would double
  count), sorted by total;
* **which requests hurt, and why?** — the top-N slowest requests by
  wall time, each attributed to its DOMINANT span kind (the
  top-level kind with the largest summed duration — the critical
  path's biggest slice), with the parts-sum coverage ratio so an
  unexplained gap is visible.

``--json`` prints one machine-readable JSON line instead of the
tables (CI and notebooks).
"""

import argparse
import json
import os
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from znicz_tpu.serving import reqtrace  # noqa: E402

#: kinds that nest inside another span — excluded from per-kind
#: totals and dominance (their time is already inside the parent)
NESTED_KINDS = frozenset(("device", "replica"))


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return None
    idx = int(q * (len(sorted_vals) - 1))
    return sorted_vals[idx]


def top_level_kinds(tree):
    """The kinds that partition THIS tree's wall time: the union of
    the serving and router top-level vocabularies for a stitched
    tree, the origin's own set otherwise."""
    if tree.get("stitched"):
        return (frozenset(reqtrace.ROUTER_TOP_LEVEL_KINDS)
                | frozenset(reqtrace.TOP_LEVEL_KINDS))
    if tree.get("origin") == "router":
        return frozenset(reqtrace.ROUTER_TOP_LEVEL_KINDS)
    return frozenset(reqtrace.TOP_LEVEL_KINDS)


def dominant_kind(tree):
    """(kind, summed_ms) of the tree's largest top-level slice — the
    critical path's dominant component.  For a STITCHED tree the
    replica's own kinds compete with the router's hop kinds, except
    ``replica_wait`` (the replica subtree re-tells that window in
    finer kinds, so keeping both would double-attribute it)."""
    kinds = top_level_kinds(tree)
    if tree.get("stitched"):
        kinds = kinds - {"replica_wait"}
    sums = {}
    for span in tree.get("spans") or ():
        if span["kind"] in kinds:
            sums[span["kind"]] = (sums.get(span["kind"], 0.0)
                                  + span["duration_ms"])
    if not sums:
        return None, 0.0
    kind = max(sums, key=lambda k: sums[k])
    return kind, round(sums[kind], 3)


def summarize(trees, top=5):
    """The analysis dict over an iterable of /debug/trace payloads."""
    per_kind = {}
    rows = []
    for tree in trees:
        if not tree or not tree.get("spans"):
            continue
        kinds = top_level_kinds(tree)
        for span in tree["spans"]:
            kind = span["kind"]
            if kind in NESTED_KINDS or kind not in kinds:
                continue
            per_kind.setdefault(kind, []).append(span["duration_ms"])
        wall = tree.get("wall_ms")
        if wall is None:
            continue
        kind, kind_ms = dominant_kind(tree)
        rows.append({
            "rid": tree.get("rid"),
            "model": tree.get("model"),
            "wall_ms": wall,
            "dominant_kind": kind,
            "dominant_ms": kind_ms,
            "parts_over_wall": (round(tree["parts_ms"] / wall, 3)
                                if tree.get("parts_ms") is not None
                                and wall else None),
            "stitched": bool(tree.get("stitched")),
        })
    kinds_out = {}
    for kind, vals in per_kind.items():
        vals.sort()
        kinds_out[kind] = {
            "count": len(vals),
            "p50_ms": round(_percentile(vals, 0.50), 3),
            "p99_ms": round(_percentile(vals, 0.99), 3),
            "total_ms": round(sum(vals), 3),
        }
    rows.sort(key=lambda r: -r["wall_ms"])
    return {
        "traces": len(rows),
        "kinds": kinds_out,
        "slowest": rows[:int(top)],
    }


def fetch_trees(base_url, limit=None):
    """Every sampled tree behind ``GET /debug/trace`` on a live
    server (router or replica)."""
    base = base_url.rstrip("/")
    with urllib.request.urlopen(base + "/debug/trace",
                                timeout=10) as resp:
        index = json.loads(resp.read())
    trees = []
    for rid in (index.get("rids") or [])[:limit]:
        try:
            with urllib.request.urlopen(
                    base + "/debug/trace/" + rid, timeout=10) as resp:
                trees.append(json.loads(resp.read()))
        except (OSError, ValueError):
            continue
    return trees


def load_trees(paths):
    trees = []
    for path in paths:
        with open(path) as f:
            doc = json.load(f)
        trees.extend(doc if isinstance(doc, list) else [doc])
    return trees


def render(report):
    lines = ["trace_summary: %d sampled trace(s)" % report["traces"],
             "",
             "| span kind | count | p50 ms | p99 ms | total ms |",
             "|---|---|---|---|---|"]
    kinds = sorted(report["kinds"].items(),
                   key=lambda kv: -kv[1]["total_ms"])
    for kind, st in kinds:
        lines.append("| %s | %d | %.3f | %.3f | %.3f |"
                     % (kind, st["count"], st["p50_ms"],
                        st["p99_ms"], st["total_ms"]))
    lines += ["", "slowest requests (dominant span kind):", ""]
    lines += ["| rid | model | wall ms | dominant | its ms | "
              "parts/wall | stitched |",
              "|---|---|---|---|---|---|---|"]
    for row in report["slowest"]:
        lines.append(
            "| %s | %s | %.3f | %s | %.3f | %s | %s |"
            % (row["rid"], row["model"] or "-", row["wall_ms"],
               row["dominant_kind"] or "-", row["dominant_ms"],
               row["parts_over_wall"], "yes" if row["stitched"]
               else "no"))
    return "\n".join(lines)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python tools/trace_summary.py",
        description="Per-kind latency breakdown + top-N slowest "
                    "requests over sampled request traces (a live "
                    "server URL or saved /debug/trace payloads).")
    parser.add_argument("source", nargs="+",
                        help="server base URL (http://...) or saved "
                             "trace JSON file(s)")
    parser.add_argument("--top", type=int, default=5,
                        help="slowest requests to list (default 5)")
    parser.add_argument("--limit", type=int, default=None,
                        help="max rids fetched from a live server")
    parser.add_argument("--json", action="store_true",
                        help="print one JSON line instead of tables")
    args = parser.parse_args(argv)
    if args.source[0].startswith("http"):
        trees = fetch_trees(args.source[0], limit=args.limit)
    else:
        trees = load_trees(args.source)
    report = summarize(trees, top=args.top)
    if args.json:
        print(json.dumps(report, sort_keys=True))
    else:
        print(render(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
